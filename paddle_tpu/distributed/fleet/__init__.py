"""``paddle.distributed.fleet`` — the hybrid-parallel engine
(python/paddle/distributed/fleet/ parity, UNVERIFIED).

The reference builds a 4D/5D process topology (dp × sharding × pp × mp ×
sep) and per-axis NCCL groups. TPU-native: ONE global
``jax.sharding.Mesh`` with named axes ('dp','sharding','pp','mp','sep',
'ep'); HybridCommunicateGroup reports the same coordinates/world-size API,
but "groups" are mesh axis names consumed by GSPMD/shard_map instead of
communicators (SURVEY.md §2.3 hybrid row)."""

from .base import (fleet, init, DistributedStrategy, Fleet, worker_num,
                   worker_index, is_first_worker, PaddleCloudRoleMaker,
                   UserDefinedRoleMaker, UtilBase)
from .topology import CommunicateTopology, HybridCommunicateGroup
from . import meta_parallel
from ..parallel_layers import (ColumnParallelLinear, RowParallelLinear,
                               VocabParallelEmbedding, ParallelCrossEntropy)
from ...framework.random import get_rng_state_tracker
from .sharding import (DygraphShardingOptimizer, group_sharded_parallel,
                       GroupShardedStage3)
from . import utils
from . import elastic

__all__ = ["fleet", "init", "DistributedStrategy", "Fleet", "UtilBase",
           "CommunicateTopology", "HybridCommunicateGroup", "meta_parallel",
           "ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy",
           "get_rng_state_tracker", "DygraphShardingOptimizer",
           "group_sharded_parallel", "GroupShardedStage3", "worker_num",
           "worker_index", "is_first_worker"]

distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
