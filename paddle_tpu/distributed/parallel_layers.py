"""Tensor-parallel layers — fleet ``meta_parallel/parallel_layers/mp_layers``
parity (UNVERIFIED).

TPU-native: weights carry NamedSharding over the 'mp' mesh axis; matmuls are
written as plain einsums with sharding constraints, and GSPMD inserts the
identity/allreduce (column) or allreduce/identity (row) pairs the reference
implements as hand-written autograd-aware comm ops. Under shard_map (the
fleet hybrid engine), the explicit-collective path is used instead."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.core import Tensor, apply
from ..nn.layer.layers import Layer
from ..nn import functional as F
from ..nn import initializer as I
from .communication import axis_in_traced_region

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy"]


def _mp_axis_and_mesh():
    from .fleet import fleet as fleet_singleton
    hcg = fleet_singleton._hcg
    if hcg is None:
        return None, None, 1
    return hcg.mp_axis_name, hcg.global_mesh, hcg.get_model_parallel_world_size()


def _ctx_mesh(mesh):
    """Mesh a trace-time sharding constraint must be built on: inside a
    (partial-)manual shard_map region — e.g. the compiled pipeline engine,
    manual over 'pipe' while 'model'/'data'/'sharding' stay auto — the
    constraint has to reference the current ABSTRACT mesh, whose manual
    axes are typed Manual (a concrete-mesh NamedSharding raises
    "Axes mentioned in vma … should be of type Manual"). Outside any
    manual region, the concrete fleet mesh."""
    try:
        am = jax.sharding.get_abstract_mesh()
        if am is not None and not am.empty and any(
                t == jax.sharding.AxisType.Manual for t in am.axis_types):
            return am
    except Exception:
        pass
    return mesh


def _constrain(data, mesh, spec):
    """Apply a sharding constraint when tracing; device_put when eager."""
    if mesh is None:
        return data
    if isinstance(data, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(
            data, NamedSharding(_ctx_mesh(mesh), spec))
    return jax.device_put(data, NamedSharding(mesh, spec))


def _constrain_tensor(t, mesh, spec, name="sharding_constraint"):
    """Sharding-constrain a Tensor WITHOUT severing the autograd tape: the
    constraint goes through ``apply`` so backward flows through it (the
    identity vjp re-places the cotangent). Shared by the TP layers here and
    fleet.utils.sequence_parallel_utils."""
    if mesh is None:
        return t

    def fn(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(_ctx_mesh(mesh), spec))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return apply(fn, t, name=name)


class ColumnParallelLinear(Layer):
    """Weight [in, out] sharded on out (the 'column'); forward output is
    sharded on the feature dim unless gather_output."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        axis, mesh, world = _mp_axis_and_mesh()
        self._axis, self._mesh = axis, mesh
        self.world_size = world
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = world > 1
        if mesh is not None:
            self.weight.set_data(_constrain(
                self.weight._data, mesh, PartitionSpec(None, axis)))
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], is_bias=True,
                default_initializer=I.Constant(0.0))
            if mesh is not None:
                self.bias.set_data(_constrain(
                    self.bias._data, mesh, PartitionSpec(axis)))
        else:
            self.bias = None

    def forward(self, x):
        axis, mesh = self._axis, self._mesh
        if axis_in_traced_region(axis):
            # explicit shard_map path: local matmul, output stays sharded
            out = F.linear(x, self.weight, self.bias)
            if self.gather_output:
                out = apply(lambda a: lax.all_gather(
                    a, axis, axis=a.ndim - 1, tiled=True), out,
                    name="mp_allgather")
            return out
        out = F.linear(x, self.weight, self.bias)
        if mesh is not None:
            spec = [None] * out.ndim
            if not self.gather_output:
                spec[-1] = axis
            out = _constrain_tensor(out, mesh, PartitionSpec(*spec))
        return out


class RowParallelLinear(Layer):
    """Weight [in, out] sharded on in (the 'row'); input is expected sharded
    on its feature dim; output gets allreduced (GSPMD: automatic)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        axis, mesh, world = _mp_axis_and_mesh()
        self._axis, self._mesh = axis, mesh
        self.world_size = world
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = world > 1
        if mesh is not None:
            self.weight.set_data(_constrain(
                self.weight._data, mesh, PartitionSpec(axis, None)))
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], is_bias=True,
                default_initializer=I.Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        axis, mesh = self._axis, self._mesh
        if axis_in_traced_region(axis):
            out = F.linear(x, self.weight, None)
            out = apply(lambda a: lax.psum(a, axis), out,
                        name="mp_allreduce")
            if self.bias is not None:
                out = out + self.bias
            return out
        out = F.linear(x, self.weight, None)
        if mesh is not None:
            out = _constrain_tensor(out, mesh,
                                    PartitionSpec(*([None] * out.ndim)))
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim over 'mp'."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        axis, mesh, world = _mp_axis_and_mesh()
        self._axis, self._mesh = axis, mesh
        self.world_size = world
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        self.weight.is_distributed = world > 1
        if mesh is not None:
            self.weight.set_data(_constrain(
                self.weight._data, mesh, PartitionSpec(axis, None)))

    def forward(self, x):
        axis = self._axis
        if axis_in_traced_region(axis):
            world = lax.axis_size(axis)
            per = self.num_embeddings // world

            def fn(ids, w):
                r = lax.axis_index(axis)
                lo = r * per
                local = ids - lo
                ok = (local >= 0) & (local < per)
                safe = jnp.where(ok, local, 0)
                out = jnp.take(w, safe, axis=0)
                out = out * ok[..., None].astype(out.dtype)
                return lax.psum(out, axis)
            return apply(fn, x, self.weight, name="vocab_parallel_embedding")
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Cross entropy over vocab-sharded logits.

    GSPMD path: plain cross entropy on constraint-sharded logits — the
    partial softmax reductions become psums automatically. shard_map path:
    explicit max/sum psums (the reference's c_softmax_with_cross_entropy)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index
        axis, mesh, _ = _mp_axis_and_mesh()
        self._axis = axis

    def forward(self, input, label):
        axis = self._axis
        if axis_in_traced_region(axis):
            ignore = self.ignore_index

            def fn(logits, lab):
                world = lax.axis_size(axis)
                v_local = logits.shape[-1]
                r = lax.axis_index(axis)
                lo = r * v_local
                lf = logits.astype(jnp.float32)
                mx = lax.pmax(jnp.max(lf, -1), axis)
                ex = jnp.exp(lf - mx[..., None])
                denom = lax.psum(jnp.sum(ex, -1), axis)
                local = lab - lo
                ok = (local >= 0) & (local < v_local)
                safe = jnp.where(ok, local, 0)
                picked = jnp.take_along_axis(lf, safe[..., None],
                                             -1)[..., 0]
                picked = jnp.where(ok, picked, 0.0)
                picked = lax.psum(picked, axis)
                loss = jnp.log(denom) + mx - picked
                if ignore is not None:
                    loss = jnp.where(lab == ignore, 0.0, loss)
                return loss[..., None]
            return apply(fn, input, label, name="parallel_cross_entropy")
        return F.softmax_with_cross_entropy(input, label,
                                            ignore_index=self.ignore_index
                                            or -100)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """``paddle.distributed.split`` parity — model-parallel linear /
    embedding created in place. Desugars to the parallel layers; the
    GSPMD sharding does the actual split, so ``num_partitions`` must
    equal the mp world size when a model-parallel group exists
    (validated below; with no mp group any value is accepted and the
    layer runs unsharded).

    Inside a captured ``static.Program`` the created layer persists on
    the Program slot (re-runs reuse weights); in plain eager each call
    creates a fresh layer, as upstream's dygraph split does."""
    from ..static.program import default_main_program

    _, _, mp_world = _mp_axis_and_mesh()
    if mp_world > 1 and num_partitions != mp_world:
        raise ValueError(
            f"dist.split: num_partitions ({num_partitions}) must equal "
            f"the model-parallel world size ({mp_world})")

    def make():
        if operation == "linear":
            in_f, out_f = int(size[0]), int(size[1])
            if axis == 1:
                return ColumnParallelLinear(
                    in_f, out_f, weight_attr=weight_attr,
                    has_bias=bias_attr is not False,
                    gather_output=gather_out)
            return RowParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                input_is_parallel=not gather_out)
        if operation == "embedding":
            return VocabParallelEmbedding(
                int(size[0]), int(size[1]), weight_attr=weight_attr)
        raise ValueError(
            f"dist.split: unknown operation {operation!r} "
            "(expected 'linear' or 'embedding')")

    prog = default_main_program()
    layer = prog._next_layer(make) if getattr(prog, "_building", False) \
        else make()
    return layer(x)
